"""Perf-history regression gate over PERF_DB.jsonl (parmmg_tpu.obs.history).

Usage:
  python tools/perf_gate.py --db PERF_DB.jsonl <record.json>
      Gate one record against its rolling baseline (same platform +
      rung + metric group; last --window non-partial records; per-key
      tolerance = max(--mad-k * 1.4826 * MAD, --rel-floor * |median|)).
      Exit 0 = pass (or no baseline yet), 91 = typed regression,
      2 = unreadable inputs.

  python tools/perf_gate.py --db PERF_DB.jsonl <record.json> --update-baseline 1
      Same, then append the (enveloped) record to the DB — the ratchet:
      improvements shift the rolling median, so the next run is gated
      against the better level. The append happens whatever the
      verdict (the DB is the append-only history; the robust median
      absorbs a bad row), but the exit code still reports it.

  python tools/perf_gate.py --backfill <repo-dir> --db PERF_DB.jsonl
      Normalize the historical BENCH_r*.json + SCALE_RUNS.jsonl under
      <repo-dir> into enveloped records and REWRITE the DB with them
      (the one non-append operation; refuses when the DB already has
      records unless --force 1).

<record.json> may be a raw bench record, an already-enveloped record,
or a BENCH driver wrapper ({"parsed": ..., "tail": ...}) — wrappers
gate their best committed record. Flags: --window N (8), --rel-floor X
(0.5), --mad-k K (4.0). Pure host code: never touches the accelerator.
"""

import json
import sys

from _cli import REPO, parse_argv  # noqa: F401 (REPO bootstraps sys.path)

from parmmg_tpu.obs import history as obs_history


def _load_candidate(path: str):
    with open(path) as f:
        doc = json.load(f)
    if not isinstance(doc, dict):
        return None
    if "cmd" in doc and "tail" in doc:
        recs = obs_history._wrapper_records(doc)
        # gate the best committed record of the wrapper (full > partial)
        recs.sort(key=lambda r: 0 if r.get("partial") else 1)
        return obs_history.normalize(recs[-1])
    return obs_history.normalize(doc)


def main():
    pos, flags = parse_argv(sys.argv[1:])
    db_path = flags.get("db", "PERF_DB.jsonl")

    if "backfill" in flags:
        recs = obs_history.backfill_records(flags["backfill"])
        if not recs:
            print(f"[perf-gate] nothing to backfill under "
                  f"{flags['backfill']}", file=sys.stderr)
            return 2
        existing = obs_history.load_db(db_path)
        if existing and flags.get("force", "") in ("", "0"):
            print(f"[perf-gate] {db_path} already holds "
                  f"{len(existing)} record(s) — refusing to rewrite "
                  "(pass --force 1)", file=sys.stderr)
            return 2
        with open(db_path, "w") as f:
            for rec in recs:
                f.write(json.dumps(rec) + "\n")
        print(f"[perf-gate] backfilled {len(recs)} record(s) -> "
              f"{db_path}")
        for rec in recs:
            print(f"  {rec['run_id']:<16s} {rec.get('metric', '?'):<28s}"
                  f" platform={rec['platform']:<8s} rung={rec['rung']}"
                  + ("  PARTIAL" if rec.get("partial") else ""))
        return 0

    if not pos:
        print(__doc__)
        return 2
    try:
        rec = _load_candidate(pos[0])
    except (OSError, json.JSONDecodeError) as exc:
        print(f"[perf-gate] unreadable record {pos[0]}: {exc}",
              file=sys.stderr)
        return 2
    if rec is None:
        print(f"[perf-gate] {pos[0]} holds no record", file=sys.stderr)
        return 2

    db = obs_history.load_db(db_path)
    res = obs_history.gate(
        db, rec,
        window=int(flags.get("window", 8)),
        rel_floor=float(flags.get("rel-floor", 0.5)),
        mad_k=float(flags.get("mad-k", 4.0)),
    )
    for line in res.lines():
        print(line)
    if flags.get("update-baseline", "") not in ("", "0"):
        obs_history.append_db(db_path, rec)
        print(f"[perf-gate] record {rec['run_id']} appended to "
              f"{db_path} (baseline ratchet)")
    return 0 if res.ok else obs_history.REGRESSION_EXIT


if __name__ == "__main__":
    sys.exit(main())
