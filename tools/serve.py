"""Adaptation-as-a-service process wrapper: spool server, solo, bench.

The in-process serving brain is `parmmg_tpu.service.JobServer`; this
tool is the PROCESS envelope around it — the pieces that only exist at
the OS boundary:

- **spool ingestion**: jobs arrive as ``<spool>/*.json`` JobSpec docs
  (the transport-free stand-in for an RPC front): each file is
  submitted and unlinked only AFTER the journal acknowledged it, so a
  crash between publish and unlink re-ingests idempotently. Permanent
  refusals move the file to ``<spool>/refused/`` next to a
  ``.refusal.json`` carrying the typed response doc; transient ones
  (queue-full) stay in place and retry next loop.
- **drain on notice/SIGTERM**: the same two drain sources the fleet
  workers honor (`PMMGTPU_PREEMPT_FILE` / maintenance notice via
  `multihost.preemption_notice`, and SIGTERM) flip the server into
  draining: in-flight work is requeued at its next phase boundary,
  admission refuses with the typed ``draining`` code, and the process
  exits :data:`~parmmg_tpu.failsafe.KILL_EXIT_CODE` (86) — the fleet
  supervisor's restart-me signal. A SIGKILL needs no cooperation at
  all: the journal replays on restart (``--replay`` is the default).
- **journal store**: any `make_store` spec (directory, ``mem://``,
  ``gs://``); `CheckpointIOError` exits 89 like every other tool.
- **bench** (``--bench``): the serve throughput rung. Fake-GCS journal
  (or a real bucket via ``PMMGTPU_GCS_BUCKET``), ``--warmup`` compile
  pre-pay, N synthetic jobs of one size class, headline
  ``jobs_per_min`` recorded as PERF_DB rung ``serve-<class>``.

- **SLO admission** (``--slo PERF_DB.jsonl``): arm
  `service.admission.SloPolicy` with the named history — explicit
  deadlines below the rolling-median ``serve-<class>`` quote are
  refused typed (``slo-infeasible``) at submit; deadline-less jobs get
  ``quote x PMMGTPU_SLO_MARGIN`` as their data-derived default.

Usage::

  python tools/serve.py --spool DIR [--journal SPEC] [--warmup 1]
      [--idle-exit S] [--trace DIR] [--status PORT]
      [--slo PERF_DB.jsonl]
  python tools/serve.py --solo spec.json [--journal SPEC]
  python tools/serve.py --bench 1 [--jobs 6] [--size-class tiny]
      [--db PERF_DB.jsonl --update 1]
"""

import argparse
import json
import os
import signal
import sys
import time

os.environ.setdefault("TF_CPP_MIN_LOG_LEVEL", "3")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)
ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

SPOOL_POLL_S = 0.2

_SIGTERM = {"hit": False}


def _on_sigterm(signum, frame):
    _SIGTERM["hit"] = True


def _classes_arg(spec):
    from parmmg_tpu.service import DEFAULT_CLASSES

    if not spec:
        return DEFAULT_CLASSES
    by_name = {c.name: c for c in DEFAULT_CLASSES}
    out = []
    for name in spec.split(","):
        name = name.strip()
        if name not in by_name:
            raise SystemExit(
                f"unknown size class {name!r} (have "
                f"{','.join(by_name)})"
            )
        out.append(by_name[name])
    return tuple(out)


def _emit_exit(tracer_dir):
    """Flush spans + counters so --serve reports see the whole story."""
    from parmmg_tpu.obs import metrics as obs_metrics
    from parmmg_tpu.obs import trace as obs_trace

    obs_trace.get_tracer().flush()
    if tracer_dir:
        obs_metrics.registry().write(tracer_dir)


def ingest_spool(server, spool):
    """Submit every spec file in the spool; returns #admitted. Files
    are unlinked only after the journal ack (idempotent re-ingest)."""
    from parmmg_tpu.service import JobSpec, ServiceRefusal

    admitted = 0
    refused_dir = os.path.join(spool, "refused")
    for name in sorted(os.listdir(spool)):
        if not name.endswith(".json"):
            continue
        path = os.path.join(spool, name)
        try:
            with open(path) as f:
                spec = JobSpec.from_doc(json.load(f))
        except (ValueError, TypeError, KeyError, OSError) as e:
            os.makedirs(refused_dir, exist_ok=True)
            doc = dict(error="BadJobError", code="bad-input",
                       transient=False, message=str(e))
            with open(os.path.join(refused_dir,
                                   name + ".refusal.json"), "w") as f:
                json.dump(doc, f, indent=1)
            os.replace(path, os.path.join(refused_dir, name))
            print(f"[serve] {name}: unparseable spec -> refused/",
                  file=sys.stderr)
            continue
        try:
            server.submit(spec)
        except ServiceRefusal as err:
            if err.transient:
                # queue-full / draining: the file IS the retry queue
                continue
            os.makedirs(refused_dir, exist_ok=True)
            with open(os.path.join(refused_dir,
                                   name + ".refusal.json"), "w") as f:
                json.dump(err.doc(), f, indent=1)
            os.replace(path, os.path.join(refused_dir, name))
            print(f"[serve] {spec.job_id}: refused ({err.code})")
            continue
        os.unlink(path)
        admitted += 1
        print(f"[serve] admitted {spec.job_id} "
              f"(tenant {spec.tenant})")
    return admitted


def drain_requested():
    from parmmg_tpu.parallel import multihost

    return _SIGTERM["hit"] or multihost.preemption_notice()


def main_server(args, server):
    """The serving loop: ingest spool -> run one batch -> repeat;
    drain on notice/SIGTERM -> exit 86; idle-exit -> 0."""
    from parmmg_tpu.failsafe import KILL_EXIT_CODE

    signal.signal(signal.SIGTERM, _on_sigterm)
    os.makedirs(args.spool, exist_ok=True)
    restored = server.replay()
    if restored:
        print(f"[serve] journal replay restored {restored} job(s)")
    idle_since = time.monotonic()
    while True:
        if drain_requested():
            server.request_drain()
            print(f"[serve] drain requested -> exiting "
                  f"{KILL_EXIT_CODE} (queue depth "
                  f"{len(server.queue)})")
            _emit_exit(args.trace)
            return KILL_EXIT_CODE
        ingest_spool(server, args.spool)
        finished = server.run_once()
        if server.draining:
            # a mid-batch drain already requeued the in-flight job
            print(f"[serve] drained mid-batch -> exiting "
                  f"{KILL_EXIT_CODE}")
            _emit_exit(args.trace)
            return KILL_EXIT_CODE
        if finished:
            idle_since = time.monotonic()
            continue
        if server.idle():
            if (args.idle_exit is not None
                    and time.monotonic() - idle_since > args.idle_exit):
                print("[serve] idle-exit: queue and spool empty")
                _emit_exit(args.trace)
                return 0
            time.sleep(SPOOL_POLL_S)


def main_solo(args, server):
    """Run exactly one spec to a terminal state and print the
    machine-readable JOB_RESULT line (the smoke's bit-identical
    baseline comes from here)."""
    from parmmg_tpu.service import ServiceRefusal, TERMINAL_STATES

    with open(args.solo) as f:
        spec_doc = json.load(f)
    from parmmg_tpu.service import JobSpec

    spec = JobSpec.from_doc(spec_doc)
    try:
        server.submit(spec)
    except ServiceRefusal as err:
        print(f"JOB_RESULT job={spec.job_id} state=rejected "
              f"code={err.code} digest=- wall=0")
        _emit_exit(args.trace)
        return 0 if not err.transient else 3
    while not server.idle():
        server.run_once()
    doc = server.journal.load(spec.job_id) or {}
    state = doc.get("state", "?")
    result = doc.get("result") or {}
    error = doc.get("error") or {}
    code = "ok" if state == "done" else error.get("code", "?")
    print(f"JOB_RESULT job={spec.job_id} state={state} code={code} "
          f"digest={result.get('digest', '-')} "
          f"wall={result.get('wall_s', 0)}")
    _emit_exit(args.trace)
    return 0 if state in TERMINAL_STATES else 1


def resolve_bench_store():
    """(spec, backend, cleanup): real bucket when PMMGTPU_GCS_BUCKET
    is set, else a hermetic fake-GCS server (the ckpt_bench idiom)."""
    bucket = os.environ.get("PMMGTPU_GCS_BUCKET")
    if bucket:
        prefix = f"parmmg-serve-bench/{os.getpid()}-{int(time.time())}"
        return f"gs://{bucket}/{prefix}", "gcs", (lambda: None)
    sys.path.insert(0, os.path.join(ROOT, "tests"))
    from fake_gcs import FakeGCS

    srv = FakeGCS()
    base = srv.start()
    os.environ["PMMGTPU_GCS_ENDPOINT"] = base
    os.environ["PMMGTPU_GCS_AUTH"] = "anon"
    return "gs://parmmg-bench/serve", "gcs-fake", srv.stop


def main_bench(args):
    """Serve-throughput rung: N synthetic jobs of one class through a
    warmed server on a (fake-)GCS journal; headline jobs_per_min."""
    import tempfile

    import jax

    from parmmg_tpu.io import medit
    from parmmg_tpu.io.ckpt_store import make_store
    from parmmg_tpu.obs import history as obs_history
    from parmmg_tpu.service import JobServer, JobSpec
    from parmmg_tpu.utils.gen import unit_cube_mesh

    classes = _classes_arg(args.size_class)
    cls = classes[0]
    spec, backend, cleanup = resolve_bench_store()
    print(f"[serve-bench] journal {spec} (backend {backend})")
    try:
        store = make_store(spec)
        server = JobServer(store, classes=classes,
                           queue_cap=max(args.jobs, 4),
                           batch_max=args.batch_max,
                           slo=getattr(args, "slo", None))
        warmup_s = server.warmup() if args.warmup else 0.0
        if args.warmup:
            print(f"[serve-bench] warmup {warmup_s}s "
                  f"({len(classes)} class(es))")
        with tempfile.TemporaryDirectory() as tmp:
            inmesh = os.path.join(tmp, "bench_cube.mesh")
            medit.save_mesh(unit_cube_mesh(2), inmesh)
            for i in range(args.jobs):
                server.submit(JobSpec(
                    job_id=f"bench-{i:03d}", inmesh=inmesh,
                    tenant=f"tenant{i % 2}", hsiz=0.45, niter=1,
                ))
            t0 = time.perf_counter()
            while not server.idle():
                server.run_once()
            wall = time.perf_counter() - t0
        docs = server.journal.jobs()
        done = sum(1 for d in docs if d.get("state") == "done")
        if done != args.jobs:
            print(f"[serve-bench] only {done}/{args.jobs} jobs done",
                  file=sys.stderr)
            return 1
        jpm = 60.0 * args.jobs / wall if wall > 0 else 0.0
        payload = dict(
            metric="jobs_per_min",
            value=round(jpm, 3),
            jobs=args.jobs,
            wall_s=round(wall, 4),
            warmup_s=round(warmup_s, 3),
            size_class=cls.name,
            batch_max=args.batch_max,
            backend=backend,
            platform=jax.devices()[0].platform,
        )
        rec = obs_history.make_record(payload, rung=f"serve-{cls.name}")
        print(f"[serve-bench] {args.jobs} jobs in {payload['wall_s']}s"
              f" -> {payload['value']} jobs/min "
              f"(warmup {payload['warmup_s']}s)")
    finally:
        cleanup()
    if args.json:
        with open(args.json, "w") as f:
            json.dump(dict(records=[rec]), f, indent=1)
        print(f"[serve-bench] record -> {args.json}")
    if args.db:
        db = obs_history.load_db(args.db)
        res = obs_history.gate(db, rec, rel_floor=args.rel_floor)
        for line in res.lines():
            print(line)
        if args.update not in ("", "0"):
            obs_history.append_db(args.db, rec)
            print(f"[serve-bench] record appended to {args.db}")
        if not res.ok:
            return obs_history.REGRESSION_EXIT
    return 0


def main() -> int:
    ap = argparse.ArgumentParser(
        description="parmmg-tpu adaptation job server"
    )
    ap.add_argument("--spool", default=None,
                    help="server mode: ingest JobSpec JSON files here")
    ap.add_argument("--solo", default=None,
                    help="run ONE spec file to a terminal state")
    ap.add_argument("--bench", default="0",
                    help="serve-throughput bench mode")
    ap.add_argument("--journal", default=None,
                    help="journal store spec (dir, mem://, gs://)")
    ap.add_argument("--warmup", default="0",
                    help="pre-pay per-class compiles before serving")
    ap.add_argument("--classes", dest="size_class", default="",
                    help="comma subset of the size-class table")
    ap.add_argument("--queue-cap", type=int, default=16)
    ap.add_argument("--batch-max", type=int, default=4)
    ap.add_argument("--idle-exit", type=float, default=None,
                    help="exit 0 after S idle seconds (smoke mode)")
    ap.add_argument("--trace", default=None,
                    help="PMMGTPU_TRACE dir for spans/events/counters")
    ap.add_argument("--status", type=int, default=None,
                    help="serve Prometheus serve/* counters + queue "
                         "occupancy at http://127.0.0.1:PORT/metrics "
                         "(0 = ephemeral port)")
    ap.add_argument("--jobs", type=int, default=6,
                    help="bench: synthetic job count")
    ap.add_argument("--json", default=None,
                    help="bench: write the enveloped record here")
    ap.add_argument("--db", default=None,
                    help="bench: PERF_DB.jsonl to gate against")
    ap.add_argument("--slo", default=None,
                    help="PERF_DB.jsonl to quote SLO admission from: "
                         "infeasible deadlines are refused typed at "
                         "submit, deadline-less jobs get quote x "
                         "PMMGTPU_SLO_MARGIN")
    ap.add_argument("--update", default="0",
                    help="bench: append the record to --db")
    ap.add_argument("--rel-floor", type=float, default=0.5,
                    help="bench: gate tolerance floor")
    args = ap.parse_args()
    args.warmup = args.warmup not in ("", "0")

    if args.trace:
        os.environ["PMMGTPU_TRACE"] = args.trace

    import jax
    from jax._src import xla_bridge as _xb

    for _accel in ("axon", "tpu", "cuda", "rocm"):
        _xb._backend_factories.pop(_accel, None)
    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_enable_x64", True)

    from parmmg_tpu.failsafe import CKPT_IO_EXIT_CODE
    from parmmg_tpu.io.ckpt_store import CheckpointIOError, make_store

    try:
        if args.bench not in ("", "0"):
            return main_bench(args)
        if not args.journal:
            raise SystemExit("--journal STORE is required "
                             "(or use --bench)")
        from parmmg_tpu.service import JobServer

        store = make_store(args.journal)
        server = JobServer(store, classes=_classes_arg(args.size_class),
                           queue_cap=args.queue_cap,
                           batch_max=args.batch_max,
                           slo=args.slo)
        if args.slo:
            print(f"[serve] SLO admission quoting from {args.slo}")
        if args.warmup:
            s = server.warmup()
            print(f"[serve] warmup {s}s")
        status = None
        if args.status is not None:
            from parmmg_tpu.service import StatusServer

            status = StatusServer(server, port=args.status).start()
            print(f"[serve] status endpoint: "
                  f"http://{status.host}:{status.port}/metrics")
        try:
            if args.solo:
                return main_solo(args, server)
            if not args.spool:
                raise SystemExit(
                    "need --spool DIR, --solo SPEC or --bench"
                )
            return main_server(args, server)
        finally:
            if status is not None:
                status.close()
    except CheckpointIOError as e:
        print(f"[serve] journal store I/O failure: {e}",
              file=sys.stderr)
        return CKPT_IO_EXIT_CODE


if __name__ == "__main__":
    sys.exit(main())
