"""Pallas-kernel smoke for the CI gate (tools/check.sh, between the
frontier stage and the obs stage).

Interpret-mode execution of EVERY registered kernel on a tiny fixture
with equivalence against its lax reference, the vmap / shard_map
dispatch legs, and the driver-level A/B the kernels contract promises:

1. registry sanity — every kernel pairs a pallas_impl with a
   lax_reference, carries a doc and an analytic cost model;
2. per-kernel interpret-vs-reference equivalence on mesh-shaped data
   (bit-exact booleans; ULP-band tolerance for the float kernels —
   the documented FMA/fusion story, see tests/test_m18_kernels.py);
3. dispatch under vmap and under shard_map (check_rep=False, the SPMD
   sweep setting);
4. driver A/B on the cube mesh: ``PMMGTPU_KERNELS=off`` twice must be
   bit-identical (the off path IS the pre-kernel chain), and
   ``off`` vs ``on`` must land equivalent meshes (element count and
   quality histogram within the kernel tolerance band).

Exit 0 = the kernel subsystem is live and equivalent; any mismatch
fails the gate.
"""

import os
import sys

os.environ.setdefault("TF_CPP_MIN_LOG_LEVEL", "3")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402
from jax._src import xla_bridge as _xb  # noqa: E402

# Pallas registers Mosaic lowerings for platform "tpu" at import time
# and refuses once "tpu" is deregistered — import it first (same
# ordering as tests/conftest.py)
import jax.experimental.pallas  # noqa: F401, E402
from jax.experimental.pallas import tpu as _pltpu  # noqa: F401, E402

for _accel in ("axon", "tpu", "cuda", "rocm"):
    _xb._backend_factories.pop(_accel, None)
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)

import hashlib  # noqa: E402

import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402

import parmmg_tpu  # noqa: F401, E402  (jax.shard_map alias)
from parmmg_tpu import kernels  # noqa: E402
from parmmg_tpu.kernels import registry  # noqa: E402
from parmmg_tpu.models.adapt import AdaptOptions, adapt  # noqa: E402
from parmmg_tpu.ops import common, quality  # noqa: E402
from parmmg_tpu.utils.gen import unit_cube_mesh  # noqa: E402

def _rtol(dtype) -> float:
    """Documented interpret-vs-reference ULP band (FMA/fusion
    differences amplified through the quality tail; see
    tests/test_m18_kernels.py)."""
    return 5e-6 if jnp.finfo(dtype).bits == 32 else 5e-11


def _close(a, b, what):
    a = np.asarray(a)
    np.testing.assert_allclose(a, np.asarray(b), rtol=_rtol(a.dtype),
                               atol=0, err_msg=what)


def check_registry() -> None:
    names = kernels.names()
    assert {"collapse_cavity", "interp_bary", "quality_vol",
            "split_midpoint"} <= set(names), names
    for n in names:
        k = registry.get(n)
        assert callable(k.pallas_impl) and callable(k.lax_reference), n
        assert k.doc and k.est_cost is not None, n
    print(f"## registry: {len(names)} kernel(s) paired "
          f"[{', '.join(names)}]")


def check_kernels(mesh) -> None:
    rng = np.random.default_rng(5)
    vert, met, tet = mesh.vert, mesh.met, mesh.tet
    with registry.use_mode("off"):
        q0, v0 = kernels.quality_vol(vert, met, tet)
    with registry.use_mode("on"):
        q1, v1 = kernels.quality_vol(vert, met, tet)
    _close(q1, q0, "quality_vol q")
    _close(v1, v0, "quality_vol vol")

    floor = common.POS_VOL_FRAC * jnp.abs(v0)
    with registry.use_mode("off"):
        g0 = kernels.collapse_cavity(vert, met, tet, floor)
    with registry.use_mode("on"):
        g1 = kernels.collapse_cavity(vert, met, tet, floor)
    f0 = np.isfinite(np.asarray(g0))
    assert (f0 == np.isfinite(np.asarray(g1))).all(), "cavity gate"
    _close(np.asarray(g1)[f0], np.asarray(g0)[f0], "collapse_cavity")

    n = tet.shape[0]
    newp = jnp.asarray(rng.normal(size=(n, 3)), dtype=vert.dtype)
    li = jnp.asarray(rng.integers(0, 4, n), dtype=jnp.int32)
    lj = jnp.asarray(rng.integers(0, 4, n), dtype=jnp.int32)
    with registry.use_mode("off"):
        ok0 = kernels.split_midpoint(vert, tet, newp, li, lj)
    with registry.use_mode("on"):
        ok1 = kernels.split_midpoint(vert, tet, newp, li, lj)
    assert (np.asarray(ok0) == np.asarray(ok1)).all(), "split_midpoint"

    ne = int(mesh.ntet)
    tids = rng.integers(0, max(ne, 1), size=256)
    vids = jnp.asarray(np.asarray(jax.device_get(tet))[tids],
                       dtype=jnp.int32)
    pts = jnp.asarray(rng.uniform(0, 1, size=(256, 3)),
                      dtype=vert.dtype)
    with registry.use_mode("off"):
        b0, m0 = kernels.interp_bary(vert, met, vids, pts)
    with registry.use_mode("on"):
        b1, m1 = kernels.interp_bary(vert, met, vids, pts)
    _close(b1, b0, "interp_bary bary")
    _close(m1, m0, "interp_bary met")
    print("## per-kernel interpret-vs-reference equivalence OK")


def check_vmap_shard_map(mesh) -> None:
    from jax.sharding import Mesh as DeviceMesh, PartitionSpec as P

    vert, met, tet = mesh.vert, mesh.met, mesh.tet

    def f(t):
        return kernels.quality_vol(vert, met, t)[0]

    half = min(256, tet.shape[0] // 2)
    ts = jnp.stack([tet[:half], tet[half:2 * half]])
    with registry.use_mode("on"):
        qp = jax.vmap(f)(ts)
    with registry.use_mode("off"):
        qr = jax.vmap(f)(ts)
    _close(qp, qr, "vmap parity")

    ndev = min(2, len(jax.devices()))
    dmesh = DeviceMesh(np.array(jax.devices()[:ndev]), ("s",))
    tflat = tet[: ndev * half]
    # parmmg-lint: disable=PML004 -- one-shot smoke: the wrapper is built exactly twice per process
    sm = jax.jit(jax.shard_map(
        f, mesh=dmesh, in_specs=P("s"), out_specs=P("s"),
        check_rep=False,
    ))
    with registry.use_mode("on"):
        qsp = sm(tflat)
    with registry.use_mode("off"):
        qsr = sm(tflat)
    _close(qsp, qsr, "shard_map parity")
    print(f"## vmap + shard_map dispatch parity OK ({ndev} device(s))")


def _digest(m) -> str:
    s = hashlib.sha256()
    for f in ("vert", "met", "tet", "tmask", "vmask", "tria", "trmask"):
        s.update(np.asarray(jax.device_get(getattr(m, f))).tobytes())
    return s.hexdigest()


def check_driver_ab() -> None:
    opts = dict(niter=1, hsiz=0.25, max_sweeps=4, hgrad=None)
    try:
        out_a, _ = adapt(unit_cube_mesh(4),
                         AdaptOptions(kernels="off", **opts))
        out_b, _ = adapt(unit_cube_mesh(4),
                         AdaptOptions(kernels="off", **opts))
        da, db = _digest(out_a), _digest(out_b)
        assert da == db, f"off-mode runs not bit-identical: {da} {db}"
        ha = quality.quality_histogram(out_a)
        out_c, _ = adapt(unit_cube_mesh(4),
                         AdaptOptions(kernels="on", **opts))
        hc = quality.quality_histogram(out_c)
    finally:
        registry.set_mode(None)
    ne_a, ne_c = int(out_a.ntet), int(out_c.ntet)
    assert abs(ne_c - ne_a) <= max(8, 0.05 * ne_a), (ne_a, ne_c)
    dq = abs(float(ha.qmin) - float(hc.qmin))
    assert dq < 5e-2, f"qmin drifted across backends: {dq}"
    print(f"## driver A/B OK: off bit-identical ({da[:12]}…), "
          f"on ne={ne_c} vs off ne={ne_a}, |dqmin|={dq:.2e}")


def main() -> int:
    check_registry()
    mesh = unit_cube_mesh(3)
    check_kernels(mesh)
    check_vmap_shard_map(mesh)
    check_driver_ab()
    print("## kernel smoke OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
