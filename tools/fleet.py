"""Elastic fleet supervisor: launch, shrink, grow — no operator.

The process half of the elastic autoscaling story
(`parmmg_tpu/parallel/elastic.py` is the in-worker half): this
supervisor launches N coordinated worker ranks (the
`tests/multihost_worker.py --elastic` workload by default), publishes
the store-backed membership manifest each launch epoch, and turns the
workers' typed exits into world reformations:

- a **notice-driven shrink**: the noticed rank exits 86 (departure,
  checkpoint committed), the survivors exit 90 (REFORM) at the same
  agreed boundary — the fleet relaunches the survivors as a world of
  N−1, which resumes from the committed epoch and re-cuts its shards
  onto the smaller device pool;
- a **capacity-restored grow**: a world running below the target size
  publishes a grow request when `multihost.capacity_restored()` fires
  (``PMMGTPU_CAPACITY_FILE`` / callback / programmatic), every rank
  exits 90, and the fleet relaunches straight at the TARGET world in
  one reformation (batch grow — each reformation costs a barrier +
  checkpoint + repartition, so 1 → N is one relaunch, not N−1);
  ``--initial-world`` launches below the target to exercise exactly
  this edge;
- a **whole-world preemption** (every rank 86/87 without a reform
  record) is a plain relaunch-and-resume at the same world size.

Worker teardown/re-init of ``jax.distributed`` happens by process
replacement: this jaxlib pins the runtime's world size at
``initialize()``, so a reformation relaunches fresh processes against
a fresh coordinator port — the store-backed manifest (not any ack from
the dying rank) carries the membership across, which is why a rank
that dies without ever acking cannot wedge the reformation.

Typed outcomes: exit 0 = the workload completed (final epoch all ranks
0, ADAPT_DIGEST relayed); exit 3 = typed refusal (the world cannot
reform: shrink below ``--min-world``, or a worker's 88-family
refusal); exit 1 = untyped failure / hang (stage watchdog).

Usage::

  python tools/fleet.py --world 2 --devices-per-rank 4 \\
      --ckpt /path/ck --trace /path/obs \\
      [--faults it0:post:preempt-notice@rank1] \\
      [--capacity-file /path/capacity] [--niter 4] \\
      [--min-world 1] [--epoch-timeout 900] [--max-epochs 6] \\
      [-- CMD ...]

The fleet itself is jax-free (stdlib only): manifests are written with
the same atomic tmp+rename discipline as `LocalFSStore`, so the
workers' store sees whole objects. ``--ckpt`` must therefore be a
local directory (workers on one host / a shared FS); object-store
fleets point the WORKERS at ``gs://`` via their own env and give the
fleet the mirror directory.
"""

import argparse
import json
import os
import socket
import subprocess
import sys
import tempfile
import time

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# the typed worker exit family (mirrors parmmg_tpu.failsafe without
# importing jax into the supervisor)
KILL = 86          # departure / whole-world preemption (ckpt committed)
PEER_LOST = 87     # watchdog conversion of a silently dead peer
MISMATCH = 88      # refusal family (fingerprint / unreformable world)
CKPT_IO = 89       # store outage past bounded retries
REFORM = 90        # survivor of an agreed reformation: relaunch me
TYPED_RCS = {0, KILL, PEER_LOST, MISMATCH, CKPT_IO, REFORM}

REFUSAL_EXIT = 3


def _atomic_write_json(path: str, doc: dict) -> None:
    tmp = f"{path}.tmp.{os.getpid()}.{time.monotonic_ns()}"
    with open(tmp, "w") as f:
        json.dump(doc, f, sort_keys=True)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)


def publish_manifest(ckdir: str, epoch: int, members, target: int,
                     reason: str) -> None:
    """The fleet-side manifest publish — same name/format as
    `parmmg_tpu.parallel.elastic.publish_manifest`, written with the
    LocalFSStore atomicity discipline so workers read whole objects."""
    os.makedirs(ckdir, exist_ok=True)
    _atomic_write_json(
        os.path.join(ckdir, f"elastic_manifest_e{epoch:05d}.json"),
        dict(format=1, epoch=epoch, world=len(members),
             members=list(members), target_world=target, reason=reason,
             ts=time.time()),
    )


def reform_kinds(ckdir: str, epoch: int):
    """kinds of the epoch's reform records ({'shrink'}, {'grow'}, ...)."""
    prefix = f"elastic_reform_e{epoch:05d}_"
    kinds = set()
    try:
        names = sorted(os.listdir(ckdir))
    except FileNotFoundError:
        return kinds
    for name in names:
        if not (name.startswith(prefix) and name.endswith(".json")):
            continue
        try:
            with open(os.path.join(ckdir, name)) as f:
                kinds.add(json.load(f).get("kind"))
        except (OSError, ValueError):
            continue
    return kinds


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def launch_epoch(args, epoch: int, members, cmd, logdir):
    """One coordinated world: rank r of this epoch is members[r]. The
    member id is the STABLE identity (drain files are per member, so a
    notice aimed at a member follows it across rank renumbering)."""
    world = len(members)
    port = _free_port() if world > 1 else None
    procs, logs = [], []
    for rank, member in enumerate(members):
        env = dict(os.environ)
        env.pop("PALLAS_AXON_POOL_IPS", None)
        env.update(
            JAX_PLATFORMS="cpu",
            PYTHONPATH=ROOT,
            PYTHONFAULTHANDLER="1",
            XLA_FLAGS=("--xla_force_host_platform_device_count="
                       f"{args.devices_per_rank}"),
            PMMGTPU_ELASTIC="1",
            PMMGTPU_ELASTIC_EPOCH=str(epoch),
            PMMGTPU_ELASTIC_TARGET=str(args.world),
            PMMGTPU_ELASTIC_MIN_WORLD=str(args.min_world),
            PMMGTPU_ELASTIC_NITER=str(args.niter),
            PMMGTPU_CKPT_DIR=args.ckpt,
            PMMGTPU_WATCHDOG=str(args.watchdog),
            PMMGTPU_PREEMPT_FILE=os.path.join(
                args.ckpt, f"fleet_preempt_m{member}"
            ),
        )
        for k in ("PMMGTPU_COORDINATOR", "PMMGTPU_NUM_PROCS",
                  "PMMGTPU_PROC_ID", "PARMMG_FAULTS",
                  "PMMGTPU_CAPACITY_FILE", "PMMGTPU_TRACE"):
            env.pop(k, None)
        if world > 1:
            env.update(
                PMMGTPU_COORDINATOR=f"127.0.0.1:{port}",
                PMMGTPU_NUM_PROCS=str(world),
                PMMGTPU_PROC_ID=str(rank),
            )
        if args.trace:
            env["PMMGTPU_TRACE"] = args.trace
        if args.capacity_file:
            env["PMMGTPU_CAPACITY_FILE"] = args.capacity_file
        if args.faults and epoch == 0:
            # fault schedules address epoch 0's rank numbering; later
            # epochs run fault-free (the recovery is what's under test)
            env["PARMMG_FAULTS"] = args.faults
        lp = os.path.join(logdir, f"e{epoch}_r{rank}_m{member}.log")
        logs.append(lp)
        procs.append(subprocess.Popen(
            cmd, env=env, stdout=open(lp, "w"),
            stderr=subprocess.STDOUT, cwd=ROOT,
        ))
    return procs, logs


def wait_epoch(procs, timeout: float):
    """Bounded wait for every rank; on overrun the world is killed and
    None returned (the zero-hang contract makes a wedged epoch a
    FAILURE, not something to wait out)."""
    deadline = time.monotonic() + timeout
    rcs = []
    try:
        for p in procs:
            rcs.append(p.wait(timeout=max(deadline - time.monotonic(),
                                          1.0)))
    except subprocess.TimeoutExpired:
        return None
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    return rcs


def main() -> int:
    ap = argparse.ArgumentParser(
        description="elastic fleet supervisor (see module docstring)"
    )
    ap.add_argument("--world", type=int, default=2,
                    help="target world size (and initial, unless "
                         "--initial-world says otherwise)")
    ap.add_argument("--initial-world", type=int, default=None,
                    help="launch below the target: the first "
                         "capacity-restored vote batch-grows straight "
                         "to --world in ONE reformation")
    ap.add_argument("--min-world", type=int, default=1)
    ap.add_argument("--devices-per-rank", type=int, default=4)
    ap.add_argument("--ckpt", default=None,
                    help="checkpoint/manifest directory (default: tmp)")
    ap.add_argument("--trace", default=None,
                    help="PMMGTPU_TRACE dir shared by every epoch")
    ap.add_argument("--faults", default=None,
                    help="PARMMG_FAULTS for epoch 0 only")
    ap.add_argument("--capacity-file", default=None)
    ap.add_argument("--niter", type=int, default=4)
    ap.add_argument("--watchdog", type=float, default=120)
    ap.add_argument("--epoch-timeout", type=float, default=900)
    ap.add_argument("--max-epochs", type=int, default=6)
    ap.add_argument("cmd", nargs="*",
                    help="worker command (default: "
                         "tests/multihost_worker.py --elastic)")
    args = ap.parse_args()

    if not args.ckpt:
        args.ckpt = tempfile.mkdtemp(prefix="parmmg_fleet_ck_")
    os.makedirs(args.ckpt, exist_ok=True)
    logdir = os.path.join(args.ckpt, "fleet_logs")
    os.makedirs(logdir, exist_ok=True)
    cmd = args.cmd or [
        sys.executable,
        os.path.join(ROOT, "tests", "multihost_worker.py"), "--elastic",
    ]

    initial = (args.initial_world if args.initial_world is not None
               else args.world)
    members = list(range(initial))
    next_member = initial
    history = []
    for epoch in range(args.max_epochs):
        reason = "launch" if epoch == 0 else history[-1]
        publish_manifest(args.ckpt, epoch, members, args.world, reason)
        world = len(members)
        print(f"[fleet] epoch {epoch}: launching world={world} "
              f"members={members} ({reason})", flush=True)
        procs, logs = launch_epoch(args, epoch, members, cmd, logdir)
        rcs = wait_epoch(procs, args.epoch_timeout)
        if rcs is None:
            print(f"[fleet] FAIL epoch {epoch}: hang (epoch timeout "
                  f"{args.epoch_timeout}s) — world killed", flush=True)
            return 1
        by_member = dict(zip(members, rcs))
        print(f"[fleet] epoch {epoch}: exits {by_member}", flush=True)

        untyped = {m: rc for m, rc in by_member.items()
                   if rc not in TYPED_RCS}
        if untyped:
            print(f"[fleet] FAIL epoch {epoch}: untyped exits "
                  f"{untyped} (logs under {logdir})", flush=True)
            return 1
        if all(rc == 0 for rc in rcs):
            # completed: relay the digest lines for the harness
            for lp in logs:
                with open(lp) as f:
                    for ln in f:
                        if ln.startswith("ADAPT_DIGEST"):
                            print(ln.rstrip(), flush=True)
            print(f"[fleet] FLEET_OK epochs={epoch + 1} "
                  f"final_world={world}", flush=True)
            return 0
        if any(rc == MISMATCH for rc in rcs):
            print(f"[fleet] FLEET_REFUSED epoch {epoch}: a rank "
                  "refused typed (unreformable world or checkpoint "
                  "mismatch, exit 88) — see logs", flush=True)
            return REFUSAL_EXIT
        if any(rc == CKPT_IO for rc in rcs):
            print(f"[fleet] FAIL epoch {epoch}: checkpoint store "
                  "outage (exit 89)", flush=True)
            return 1
        if any(rc == 0 for rc in rcs):
            # a reformation is collectively agreed: a mix of finished
            # and reforming ranks breaks the protocol
            print(f"[fleet] FAIL epoch {epoch}: inconsistent exits "
                  f"{by_member} (finished ranks next to reforming "
                  "ones)", flush=True)
            return 1

        departed = [m for m, rc in by_member.items() if rc == KILL]
        survivors = [m for m, rc in by_member.items()
                     if rc in (REFORM, PEER_LOST)]
        kinds = reform_kinds(args.ckpt, epoch)
        if "shrink" in kinds or (departed and not kinds):
            members = survivors
            history.append(f"shrink: members {departed} departed")
        elif "grow" in kinds:
            # batch grow: straight to the target in one relaunch —
            # mirrors ElasticCoordinator's one-reformation grow vote
            grown = args.world
            members = survivors + departed  # departed: none on grow
            while len(members) < grown:
                members.append(next_member)
                next_member += 1
            history.append(f"grow: capacity restored, batch to "
                           f"{grown}")
        else:
            # whole-world preemption without a reform record: plain
            # checkpoint-backed relaunch at the same size
            members = survivors + departed
            history.append("resume: whole-world preemption")
        if len(members) < args.min_world:
            print(f"[fleet] FLEET_REFUSED: reformation would leave "
                  f"{len(members)} member(s), below --min-world "
                  f"{args.min_world} — the checkpoint stands; rerun "
                  "when capacity returns", flush=True)
            return REFUSAL_EXIT
    print(f"[fleet] FAIL: {args.max_epochs} epochs without completion",
          flush=True)
    return 1


if __name__ == "__main__":
    sys.exit(main())
