"""Shared bits for the tools/ scripts: flag-aware argv parsing and the
repo bootstrap (single definition so parsing bugs can't fork between
tools)."""

import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)


def parse_argv(argv):
    """Split argv into (positionals, {flag: value}). Every `--flag`
    consumes the next token as its value, so flag values are never
    mistaken for positionals (`--stall 900` must not become n=900)."""
    pos, flags = [], {}
    i = 0
    while i < len(argv):
        a = argv[i]
        if a.startswith("--"):
            if i + 1 >= len(argv):
                raise SystemExit(f"flag {a} needs a value")
            flags[a[2:]] = argv[i + 1]
            i += 2
        else:
            pos.append(a)
            i += 1
    return pos, flags
