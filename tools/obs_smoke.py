"""Observability smoke for the CI gate (tools/check.sh, between the
chaos stage and tier-1).

One tiny traced adapt run on the hermetic CPU harness, then the
contract checks of the obs subsystem end to end:

1. the trace directory holds a structurally valid Chrome trace JSON
   (loads via ``json``, every event carries name/ph/ts/pid/tid, at
   least one complete "X" span with a duration) and a JSONL line log;
2. span counts are nonzero and the span tree contains the driver's
   root + phase + sweep spans;
3. the metrics registry recorded the run (ops counters == the
   driver-reported history totals) and its per-rank file merges;
4. `tools/obs_report.py`'s renderer parses the directory and the
   report names the phase table and operator counts.

Exit 0 = the observability surface is live; any mismatch fails the
gate — the perf arc must never go blind again.
"""

import json
import os
import shutil
import sys
import tempfile

os.environ.setdefault("TF_CPP_MIN_LOG_LEVEL", "3")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402
from jax._src import xla_bridge as _xb  # noqa: E402

for _accel in ("axon", "tpu", "cuda", "rocm"):
    _xb._backend_factories.pop(_accel, None)
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)

from parmmg_tpu.obs import costs as obs_costs  # noqa: E402
from parmmg_tpu.obs import metrics as obs_metrics  # noqa: E402
from parmmg_tpu.obs import report as obs_report  # noqa: E402
from parmmg_tpu.obs import trace as obs_trace  # noqa: E402
from parmmg_tpu.models.adapt import AdaptOptions, adapt  # noqa: E402
from parmmg_tpu.utils.gen import unit_cube_mesh  # noqa: E402


def main() -> int:
    tmp = tempfile.mkdtemp(prefix="parmmg_obs_smoke_")
    try:
        tr = obs_trace.Tracer(tmp)
        obs_metrics.registry().reset()
        obs_costs.collector().reset()
        out, info = adapt(
            unit_cube_mesh(2),
            AdaptOptions(hsiz=0.5, niter=1, max_sweeps=3, hgrad=None,
                         polish_sweeps=0),
            tracer=tr,
        )

        # 1. Chrome trace JSON validity
        path = os.path.join(tmp, "trace_rank0.json")
        with open(path) as f:
            doc = json.load(f)
        events = doc["traceEvents"]
        spans = [e for e in events if e.get("ph") == "X"]
        assert spans, "no complete spans in the Chrome trace"
        for e in events:
            for key in ("name", "ph", "pid", "tid"):
                assert key in e, (key, e)
            if e["ph"] != "M":   # metadata events carry no timestamp
                assert "ts" in e, e
        for e in spans:
            assert "dur" in e and e["dur"] >= 0, e
        assert os.path.exists(
            os.path.join(tmp, "events_rank0.jsonl")
        ), "no JSONL event log"
        print(f"[obs-smoke] chrome trace valid: {len(spans)} spans, "
              f"{len(events)} events")

        # 2. the span tree covers the driver structure
        names = {e["name"] for e in spans}
        for want in ("adapt", "phase:sweeps", "iteration"):
            assert want in names, (want, sorted(names))
        print("[obs-smoke] span tree contains root/phase/iteration")

        # 3. counter exactness vs the driver history
        reg = obs_metrics.registry()
        hist = [r for r in info["history"] if "nsplit" in r]
        for key, col in (("ops/split_accepted", "nsplit"),
                         ("ops/collapse_accepted", "ncollapse"),
                         ("ops/swap_accepted", "nswap")):
            want = sum(r[col] for r in hist)
            got = reg.counter(key).value
            assert got == want, (key, got, want)
        merged = obs_metrics.merge_dir(tmp)
        assert merged is not None and merged["world"] == 1
        assert merged["counters"]["sweeps"] == len(hist)
        print(f"[obs-smoke] counters exact over {len(hist)} sweeps; "
              "rank merge OK")

        # 4. cost attribution (PR 8): the traced run captured an XLA
        # cost doc for the fused sweep program, and the HBM watermark
        # gauges recorded phase-boundary snapshots
        docs = obs_costs.load_cost_docs(tmp)
        assert "remesh_sweeps" in docs, sorted(docs)
        assert docs["remesh_sweeps"].get("flops", 0) > 0, docs
        assert docs["remesh_sweeps"].get("bytes_accessed", 0) > 0, docs
        s = obs_report.summarize(tmp)
        cost_row = next(
            (r for r in s["costs"] if r["name"] == "remesh_sweeps"),
            None,
        )
        assert cost_row is not None and cost_row["bound"] in (
            "compute", "memory",
        ), s["costs"]
        assert cost_row["calls"] > 0 and cost_row["mean_s"] > 0
        assert s["memory"]["peak_bytes"] > 0, s["memory"]
        assert s["memory"]["phase_bytes"], s["memory"]
        print(f"[obs-smoke] cost doc captured "
              f"(bound={cost_row['bound']}, "
              f"intensity={cost_row['intensity']:.2f}); HBM peak "
              f"{s['memory']['peak_bytes'] / 1e6:.1f} MB "
              f"({s['memory']['source']})")

        # 5. the report renders, including the new cost/memory sections
        text = obs_report.render(tmp)
        assert "phase breakdown" in text and "operators" in text
        assert "adapt" in text
        assert "cost attribution" in text, text
        assert "HBM peak bytes" in text, text
        print("[obs-smoke] obs_report renders the run incl. "
              "cost/memory sections")
        return 0
    finally:
        shutil.rmtree(tmp, ignore_errors=True)


if __name__ == "__main__":
    sys.exit(main())
