#!/bin/bash
# staged xl (n=16) warm+run: the prep chain is one giant analysis-shape
# compile that can exceed the default 90-min stall on a loaded tunnel —
# give it ONE long-capped attempt (--attempts 1: a second identical
# attempt would just re-time-out), then warm the rest, then measure.
#
# Every stage's rc is captured and a failed warm ABORTS before
# scale_run: warm_ops' contract is that a scripted warm+run must not
# proceed into the cold-compile livelock on a half-warm cache —
# scale_run's 2700 s stall is far below the cold prep compile budget
# (10800 s), so running half-warm just burns its 4 retries mid-compile
# and caches nothing (ADVICE r5).
set -u
cd /root/repo || exit 1
python tools/warm_ops.py 16 0.02 --tight 1 --stall 10800 --attempts 1 --ops prep
rc=$?
echo "## stage prep rc=$rc"
[ $rc -ne 0 ] && exit $rc
python tools/warm_ops.py 16 0.02 --tight 1 --stall 5400 --ops compact,unique_edges,split,collapse,swap32,build_adjacency,swap23,smooth,histogram,polish
rc=$?
echo "## stage rest rc=$rc"
[ $rc -ne 0 ] && exit $rc
# measured stage runs on the disk cache the warm stages just filled.
# NOTE the budget is an EXPLOSION guard, not 0: jax logs "Compiling"
# before the persistent-cache lookup, so even a fully warmed run traces
# each program once (disk hits, seconds each) — the warm-cache
# steady_recompiles==0 contract is bench.py's in-process steady phase.
# What must never happen here is per-sweep retracing (PML004 class):
# the n=16 run executes ~20 sweeps over ~15 distinct programs, so >64
# sweep-phase compiles means something retraces per sweep — fail loudly
# via lint.contracts.run_adapt_with_budget instead of recording a
# silently-livelocked number
PARMMG_RETRACE_BUDGETS="sweeps=64" python tools/scale_run.py 16 0.02 --tight 1 --stall 2700 --retries 4
rc=$?
echo "## stage run rc=$rc"
exit $rc
