#!/bin/bash
# staged xl (n=16) warm+run: the prep chain is one giant analysis-shape
# compile that can exceed the default 90-min stall on a loaded tunnel —
# give it ONE long-capped attempt (--attempts 1: a second identical
# attempt would just re-time-out), then warm the rest, then measure.
#
# Every stage's rc is captured and a failed warm ABORTS before
# scale_run: warm_ops' contract is that a scripted warm+run must not
# proceed into the cold-compile livelock on a half-warm cache —
# scale_run's 2700 s stall is far below the cold prep compile budget
# (10800 s), so running half-warm just burns its 4 retries mid-compile
# and caches nothing (ADVICE r5).
#
# Each stage also runs under an OUTER per-stage timeout watchdog (PR 3):
# the python tools' --stall watchdogs only fire while their monitor
# thread is alive — a wedged process (stuck compile, dead watchdog
# thread, hung device) would otherwise hang the whole ladder. The outer
# `timeout` records the stage as failed (rc 124/137) and aborts instead
# of hanging; budgets are the stage's own stall cap plus slack for
# retries and process startup.
set -u
cd /root/repo || exit 1

run_stage() {
    # run_stage <name> <timeout_s> <cmd...>: stage under a watchdog;
    # echoes the rc line the ladder logs key off and returns the rc.
    # PARMMG_STAGE_BUDGET_S (the obs never-blind contract) is exported
    # just under the outer timeout, so the python tools commit a
    # PARTIAL BENCH JSON (marked "partial": true with the phase the
    # budget died in) before the watchdog's SIGKILL can silence them —
    # rc 124 now means "the partial record is the result", not "the
    # trajectory is blind".
    local name=$1 tmo=$2 rc
    shift 2
    env PARMMG_STAGE_BUDGET_S=$((tmo - 300)) timeout -k 30 "$tmo" "$@"
    rc=$?
    if [ "$rc" -eq 124 ] || [ "$rc" -eq 137 ]; then
        echo "## stage $name rc=$rc (watchdog timeout after ${tmo}s)"
    else
        echo "## stage $name rc=$rc"
    fi
    if [ -n "${STAGE_JSON:-}" ] && [ -f "$STAGE_JSON" ]; then
        # the committed (full or partial) record path, per stage
        echo "## stage $name bench_json=$STAGE_JSON"
    fi
    return "$rc"
}

# prep stall 10800 s + 900 s slack (startup, device init, teardown)
run_stage prep 11700 \
    python tools/warm_ops.py 16 0.02 --tight 1 --stall 10800 --attempts 1 --ops prep \
    || exit $?
# rest stall 5400 s x default 2 attempts + slack
run_stage rest 11700 \
    python tools/warm_ops.py 16 0.02 --tight 1 --stall 5400 --ops compact,unique_edges,split,collapse,swap32,build_adjacency,swap23,smooth,histogram,polish \
    || exit $?
# measured stage runs on the disk cache the warm stages just filled.
# NOTE the budget is an EXPLOSION guard, not 0: jax logs "Compiling"
# before the persistent-cache lookup, so even a fully warmed run traces
# each program once (disk hits, seconds each) — the warm-cache
# steady_recompiles==0 contract is bench.py's in-process steady phase.
# What must never happen here is per-sweep retracing (PML004 class):
# the n=16 run executes ~20 sweeps over ~15 distinct programs, so >64
# sweep-phase compiles means something retraces per sweep — fail loudly
# via lint.contracts.run_adapt_with_budget instead of recording a
# silently-livelocked number.
# watchdog: 2700 s stall x (1 + 4 retries) + slack. The stage always
# commits its record to BENCH_xl_run.json — a full measurement or a
# "partial": true marker naming where the budget died (scale_run's
# PARMMG_STAGE_BUDGET_S deadline + all-stalled fallback).
STAGE_JSON=BENCH_xl_run.json run_stage run 15300 \
    env PARMMG_RETRACE_BUDGETS="sweeps=64" \
    python tools/scale_run.py 16 0.02 --tight 1 --stall 2700 --retries 4 \
        --bench-json BENCH_xl_run.json
run_rc=$?

# multi-process distributed rung (PR 17): the sharded driver with the
# closed-loop balancer on, through bench.py's deadline-armed worker so
# a budget death still commits a partial record. The run_dist record
# carries the converged-sweep parity triple AND the first-class
# migration/balance cost fields (migrate_cost.cells / payload_bytes /
# rebalances / wall_s) that the perf gate tracks alongside imbalance.
STAGE_JSON=BENCH_dist_run.json run_stage dist 5400 \
    python -c "$(cat <<'PYEOF'
import json
import bench
rec = bench._attempt(
    dict(dist=True, n=8, hsiz=0.08, nparts=2), 4800
)
with open("BENCH_dist_run.json", "w") as f:
    json.dump(rec, f)
print(json.dumps(rec))
raise SystemExit(1 if rec.get("partial") else 0)
PYEOF
)"
dist_rc=$?
[ "$run_rc" -eq 0 ] && run_rc=$dist_rc

# perf-history gate (PR 8): every rung's committed record — full or
# partial — is appended to the PERF_DB trajectory and gated against its
# rolling (platform, rung) baseline; the verdict line per rung is part
# of the ladder log. A regression does not retro-fail the measurement
# (the record IS the result) but the typed rc is surfaced.
for bj in BENCH_xl_run.json BENCH_dist_run.json; do
    if [ -f "$bj" ]; then
        python tools/perf_gate.py --db PERF_DB.jsonl "$bj" \
            --update-baseline 1
        echo "## stage ${bj%.json} perf-gate rc=$? (record appended to PERF_DB.jsonl)"
    fi
done
exit $run_rc
