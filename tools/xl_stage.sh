#!/bin/bash
# staged xl (n=16) warm+run: the prep chain is one giant analysis-shape
# compile that can exceed the default 90-min stall on a loaded tunnel —
# give it ONE long-capped attempt, then warm the rest, then measure.
cd /root/repo
python tools/warm_ops.py 16 0.02 --tight 1 --stall 10800 --ops prep
echo "## stage prep rc=$?"
python tools/warm_ops.py 16 0.02 --tight 1 --stall 5400 --ops compact,unique_edges,split,collapse,swap32,build_adjacency,swap23,smooth,histogram,polish
echo "## stage rest rc=$?"
python tools/scale_run.py 16 0.02 --tight 1 --stall 2700 --retries 4
echo "## stage run rc=$?"
